"""L2 tests: collectives and sparse point-to-point exchange (sequential).

The 4-part asymmetric neighbor-graph fixture mirrors the spirit of the
reference conformance suite (reference: test/test_interfaces.jl:19-287),
re-derived 0-based for this framework:

    part 0 receives from [2, 3]      part 0 sends to [1, 3]
    part 1 receives from [0]         part 1 sends to [2]
    part 2 receives from [1, 3]      part 2 sends to [0, 3]
    part 3 receives from [0, 2]      part 3 sends to [0, 2]
"""
import operator

import numpy as np
import pytest

from partitionedarrays_jl_tpu import (
    ERROR_DISCOVER_PARTS_SND,
    Table,
    discover_parts_snd,
    emit,
    exchange,
    exchange_into,
    gather,
    gather_all,
    get_main_part,
    iscan,
    iscan_all,
    iscan_main,
    xscan_main,
    map_parts,
    preduce,
    reduce_all,
    reduce_main,
    scatter,
    sequential,
    sum_parts,
    xscan,
    xscan_all,
)

RCV = [[2, 3], [0], [1, 3], [0, 2]]
SND = [[1, 3], [2], [0, 3], [0, 2]]


def _parts(n=4):
    return sequential.get_part_ids(n)


def _pdata(rows, dtype=np.int64):
    return map_parts(
        lambda p: np.asarray(rows[p], dtype=dtype), _parts(len(rows))
    )


def test_gather_scalar():
    parts = _parts()
    vals = map_parts(lambda p: 10 * (p + 1), parts)
    g = gather(vals)
    assert list(get_main_part(g)) == [10, 20, 30, 40]
    assert len(g.get_part(1)) == 0
    ga = gather_all(vals)
    for p in range(4):
        assert list(ga.get_part(p)) == [10, 20, 30, 40]


def test_gather_vector_payload_builds_table():
    rows = [[0, 1], [], [2], [3, 4, 5]]
    g = gather(_pdata(rows))
    t = get_main_part(g)
    assert isinstance(t, Table)
    assert [list(r) for r in t] == rows
    assert len(gather(_pdata(rows)).get_part(2)) == 0


def test_scatter_scalar_and_table():
    parts = _parts()
    src = map_parts(
        lambda p: np.array([5, 6, 7, 8]) if p == 0 else np.array([], dtype=np.int64),
        parts,
    )
    s = scatter(src)
    assert list(s) == [5, 6, 7, 8]

    rows = [[1, 2], [3], [], [4, 5]]
    srct = map_parts(
        lambda p: Table.from_rows(rows) if p == 0 else Table.empty(np.int64), parts
    )
    st = scatter(srct)
    assert [list(st.get_part(p)) for p in range(4)] == rows


def test_emit():
    parts = _parts()
    vals = map_parts(lambda p: np.array([p + 1.0, 2.0]) if p == 0 else np.zeros(0), parts)
    e = emit(vals)
    for p in range(4):
        assert list(e.get_part(p)) == [1.0, 2.0]


def test_reductions():
    parts = _parts()
    vals = map_parts(lambda p: p + 1, parts)
    rm = reduce_main(operator.add, vals, 0)
    assert get_main_part(rm) == 10
    ra = reduce_all(operator.add, vals, 0)
    assert list(ra) == [10, 10, 10, 10]
    assert preduce(operator.mul, vals, 1) == 24
    assert sum_parts(vals) == 10


def test_scans():
    parts = _parts()
    vals = map_parts(lambda p: p + 1, parts)  # 1,2,3,4
    assert list(iscan(operator.add, vals, init=0)) == [1, 3, 6, 10]
    s, total = iscan(operator.add, vals, init=0, with_total=True)
    assert list(s) == [1, 3, 6, 10] and total == 10
    sm = iscan_main(operator.add, vals, init=0)
    assert list(get_main_part(sm)) == [1, 3, 6, 10]
    assert len(sm.get_part(1)) == 0
    sa, total = iscan_all(operator.add, vals, init=0, with_total=True)
    for p in range(4):
        assert list(sa.get_part(p)) == [1, 3, 6, 10]
    assert list(xscan(operator.add, vals, init=0)) == [0, 1, 3, 6]
    xs, total = xscan_all(operator.add, vals, init=0, with_total=True)
    assert list(xs.get_part(2)) == [0, 1, 3, 6] and total == 10
    # init participates (reference semantics: b[0] = op(init, b[0]))
    assert list(iscan(operator.add, vals, init=5)) == [6, 8, 11, 15]


def test_exchange_fixed_size():
    parts_rcv = _pdata(RCV, np.int32)
    parts_snd = _pdata(SND, np.int32)
    # part p sends value 100*p + q to neighbor q
    data_snd = map_parts(
        lambda p, snd: np.array([100 * p + int(q) for q in snd], dtype=np.int64),
        _parts(),
        parts_snd,
    )
    data_rcv = exchange(data_snd, parts_rcv, parts_snd)
    for p in range(4):
        got = list(data_rcv.get_part(p))
        expected = [100 * q + p for q in RCV[p]]
        assert got == expected


def test_exchange_table_payload_two_phase():
    parts_rcv = _pdata(RCV, np.int32)
    parts_snd = _pdata(SND, np.int32)
    # part p sends to neighbor q a row [p]*(p+1) — variable length per sender
    data_snd = map_parts(
        lambda p, snd: Table.from_rows(
            [np.full(p + 1, 10 * p + int(q), dtype=np.int64) for q in snd]
        ),
        _parts(),
        parts_snd,
    )
    data_rcv = exchange(data_snd, parts_rcv, parts_snd)
    for p in range(4):
        t = data_rcv.get_part(p)
        assert isinstance(t, Table)
        for i, q in enumerate(RCV[p]):
            assert list(t[i]) == [10 * q + p] * (q + 1)


def test_exchange_into_with_combine_manual():
    parts_rcv = _pdata(RCV, np.int32)
    parts_snd = _pdata(SND, np.int32)
    data_snd = map_parts(
        lambda p, snd: np.full(len(snd), float(p + 1)), _parts(), parts_snd
    )
    data_rcv = map_parts(lambda rcv: np.zeros(len(rcv)), parts_rcv)
    exchange_into(data_rcv, data_snd, parts_rcv, parts_snd)
    for p in range(4):
        assert list(data_rcv.get_part(p)) == [float(q + 1) for q in RCV[p]]


def test_exchange_rejects_inconsistent_graph():
    parts_rcv = _pdata([[1], [], [], []], np.int32)
    parts_snd = _pdata([[], [], [0], []], np.int32)  # not the transpose
    data_snd = map_parts(lambda snd: np.zeros(len(snd)), parts_snd)
    data_rcv = map_parts(lambda rcv: np.zeros(len(rcv)), parts_rcv)
    with pytest.raises(AssertionError):
        exchange_into(data_rcv, data_snd, parts_rcv, parts_snd)


def test_discover_parts_snd_fallback():
    parts_rcv = _pdata(RCV, np.int32)
    snd = discover_parts_snd(parts_rcv)
    assert [sorted(snd.get_part(p)) for p in range(4)] == [sorted(s) for s in SND]


def test_discover_parts_snd_with_neighbor_superset():
    # symmetric superset: union of rcv and snd edges per part
    nbors = [sorted(set(RCV[p]) | set(SND[p])) for p in range(4)]
    parts_rcv = _pdata(RCV, np.int32)
    neighbors = _pdata(nbors, np.int32)
    snd = discover_parts_snd(parts_rcv, neighbors)
    assert [sorted(snd.get_part(p)) for p in range(4)] == [sorted(s) for s in SND]


def test_discover_parts_snd_error_flag():
    # reference: the runtime guard turns the non-scalable path into an error
    # (src/Interfaces.jl:498-512, test/test_interfaces.jl:171-173)
    parts_rcv = _pdata(RCV, np.int32)
    ERROR_DISCOVER_PARTS_SND[0] = True
    try:
        with pytest.raises(RuntimeError):
            discover_parts_snd(parts_rcv)
    finally:
        ERROR_DISCOVER_PARTS_SND[0] = False


def test_xscan_main_and_iscan_main_with_total():
    """MAIN-resident scan variants (reference: src/Interfaces.jl:291-340):
    only part 0 receives the scanned sequence; the with_total form also
    reduces the full sum."""
    import partitionedarrays_jl_tpu as pa

    def driver(parts):
        a = map_parts(lambda p: p + 1, parts)  # 1, 2, 3, 4
        xm = pa.xscan_main(operator.add, a, init=10)
        np.testing.assert_array_equal(np.asarray(xm.get_part(0)), [10, 11, 13, 16])
        xm2, total = pa.xscan_main(operator.add, a, init=0, with_total=True)
        assert total == 10
        im = iscan_main(operator.add, a, init=0)
        np.testing.assert_array_equal(np.asarray(im.get_part(0)), [1, 3, 6, 10])
        return True

    assert sequential.prun(driver, 4)
