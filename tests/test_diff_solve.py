"""Differentiable distributed solve: jax.grad through the compiled CG via
the implicit-function-theorem adjoint (one extra solve per backward pass).
Checked against central finite differences on a truly SPD system."""
import jax
import jax.numpy as jnp
import numpy as np

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.parallel.tpu import (
    DeviceVector,
    device_matrix,
    make_diff_solve_fn,
)

N = 40


def _spd_tridiag(parts):
    """Eliminated-boundary 1-D Laplacian: genuinely SPD (unlike the
    Dirichlet-identity-row driver systems, which are nonsymmetric)."""
    rows = pa.prange(parts, N)

    def coo(i):
        g = np.asarray(i.oid_to_gid)
        I = [g]
        J = [g]
        V = [np.full(len(g), 2.0)]
        for off in (-1, 1):
            gj = g + off
            k = (gj >= 0) & (gj < N)
            I.append(g[k])
            J.append(gj[k])
            V.append(np.full(int(k.sum()), -1.0))
        return np.concatenate(I), np.concatenate(J), np.concatenate(V)

    c = pa.map_parts(coo, rows.partition)
    I = pa.map_parts(lambda t: t[0], c)
    J = pa.map_parts(lambda t: t[1], c)
    V = pa.map_parts(lambda t: t[2], c)
    cols = pa.add_gids(rows, J)
    return pa.PSparseMatrix.from_coo(I, J, V, rows, cols, ids="global")


def test_grad_through_compiled_solve_matches_fd():
    def driver(parts):
        A = _spd_tridiag(parts)
        dA = device_matrix(A, parts.backend)
        f = make_diff_solve_fn(dA, tol=1e-13)
        b = pa.PVector(
            pa.map_parts(
                lambda i: np.sin(np.asarray(i.lid_to_gid, float)),
                A.cols.partition,
            ),
            A.cols,
        )
        db = DeviceVector.from_pvector(b, parts.backend, dA.col_layout)
        w = np.cos(np.arange(dA.col_layout.W) * 0.1)
        wj = jnp.asarray(np.tile(w, (dA.col_layout.P, 1)))

        def loss(bv):
            return jnp.sum((f(bv) * wj) ** 2)

        g = jax.grad(loss)(db.data)
        b0 = np.asarray(db.data)
        rng = np.random.default_rng(0)
        for _ in range(5):
            p = int(rng.integers(0, dA.col_layout.P))
            i = dA.col_layout.o0 + int(
                rng.integers(0, int(dA.col_layout.noids[p]))
            )
            eps = 1e-6
            bp = b0.copy()
            bp[p, i] += eps
            bm = b0.copy()
            bm[p, i] -= eps
            fd = (
                float(loss(jnp.asarray(bp))) - float(loss(jnp.asarray(bm)))
            ) / (2 * eps)
            an = float(np.asarray(g)[p, i])
            assert abs(fd - an) / max(abs(an), 1e-10) < 1e-6, (p, i, fd, an)
        return True

    assert pa.prun(driver, pa.tpu, 4)


def test_solution_matches_host_cg():
    def driver(parts):
        A = _spd_tridiag(parts)
        b = pa.PVector.full(1.0, A.cols)
        x_host, info = pa.cg(A, b, tol=1e-13, maxiter=400)
        dA = device_matrix(A, parts.backend)
        f = make_diff_solve_fn(dA, tol=1e-13, maxiter=400)
        db = DeviceVector.from_pvector(b, parts.backend, dA.col_layout)
        x_dev = DeviceVector(
            f(db.data), A.rows, dA.col_layout, parts.backend
        ).to_pvector()
        got = pa.gather_pvector(x_dev)
        np.testing.assert_allclose(got, pa.gather_pvector(x_host), atol=1e-10)
        return True

    assert pa.prun(driver, pa.tpu, 4)


def test_diff_solve_on_node_block_lowering():
    """Regression (r4 review): make_diff_solve_fn read dA.oh_vals.dtype,
    which is None on the node-block A_oh path — differentiable solves
    must work on multi-part SD/BSR lowerings."""
    from partitionedarrays_jl_tpu.models.elasticity_tet import (
        assemble_elasticity_tet,
    )
    from partitionedarrays_jl_tpu.parallel.tpu import (
        device_matrix, make_diff_solve_fn,
    )

    def driver(parts):
        A, b, xh, x0 = assemble_elasticity_tet(parts, (4, 4, 4))
        dA = device_matrix(A, parts.backend)
        assert dA.ohb_bs == 3 and dA.oh_vals is None
        fn = make_diff_solve_fn(dA, tol=1e-8, maxiter=400)
        assert fn is not None
        return True

    assert pa.prun(driver, pa.tpu, 4)
